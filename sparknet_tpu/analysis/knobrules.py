"""KR rules — the SPARKNET_* knob registry contract.

The registry in ``utils/knobs.py`` is the single source of truth for
the env surface; these rules keep code, registry, and KNOBS.md from
drifting apart:

  KR001  a SPARKNET_* name used in code (call argument, env subscript)
         that is not registered
  KR002  an env read of a SPARKNET_* literal that bypasses the registry
         (os.environ.get / os.getenv / os.environ[...] outside
         utils/knobs.py) — writes and scrub-pops are allowed, reads
         must go through knobs.* or a helper that delegates there
  KR003  dead registration: a live knob whose name appears nowhere in
         scanned code or the tier-1 shell runner
  KR004  registry hygiene: a knob missing its doc line or owner
  KR005  KNOBS.md drift: the committed file does not match
         ``knobs_md()`` output

The registry itself is imported at lint time — it is stdlib-only by
design, so this keeps the linter runnable without JAX.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, SourceFile, dotted

_KNOB_RE = re.compile(r"^SPARKNET_[A-Z0-9_]+$")
_KNOBS_MODULE = "sparknet_tpu/utils/knobs.py"
_SHELL_RUNNERS = ("tools/run_tier1.sh",)


def _registry():
    from sparknet_tpu.utils import knobs
    return knobs


def _is_env_expr(node: ast.AST) -> str | None:
    """'read' / 'write' / 'pop' when node is an os.environ expression
    with a literal name; the literal is returned via ``node._knob``."""
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname == "os.getenv" and node.args and \
                isinstance(node.args[0], ast.Constant):
            node._knob = node.args[0].value  # type: ignore[attr-defined]
            return "read"
        if fname in ("os.environ.get", "os.environ.pop") and node.args \
                and isinstance(node.args[0], ast.Constant):
            node._knob = node.args[0].value  # type: ignore[attr-defined]
            return "pop" if fname.endswith("pop") else "read"
    if isinstance(node, ast.Subscript) and \
            dotted(node.value) == "os.environ" and \
            isinstance(node.slice, ast.Constant):
        node._knob = node.slice.value  # type: ignore[attr-defined]
        return "write" if isinstance(node.ctx, (ast.Store, ast.Del)) \
            else "read"
    return None


def _literal_knob_args(node: ast.Call) -> list[str]:
    out = []
    for a in (*node.args, *(kw.value for kw in node.keywords)):
        if isinstance(a, ast.Constant) and isinstance(a.value, str) and \
                _KNOB_RE.match(a.value):
            out.append(a.value)
    return out


def check(project: Project) -> list[Finding]:
    knobs = _registry()
    registered = {k.name for k in knobs.all_knobs()}
    live = {k.name for k in knobs.all_knobs() if not k.removed}
    used: set[str] = set()
    findings: list[Finding] = []

    def hit(sf: SourceFile, rule: str, sev: str, line: int, msg: str,
            fix: str = "") -> None:
        f = project.finding(sf, rule, sev, line, msg, fix)
        if f:
            findings.append(f)

    for sf in project.files:
        in_registry_module = sf.rel == _KNOBS_MODULE
        # every SPARKNET_* string constant anywhere counts as "used"
        # (env passthrough tuples, child-env dicts, shell contracts)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _KNOB_RE.match(node.value):
                used.add(node.value)
        if in_registry_module:
            continue
        for node in ast.walk(sf.tree):
            kind = _is_env_expr(node)
            if kind is not None:
                name = getattr(node, "_knob", "")
                if not (isinstance(name, str) and _KNOB_RE.match(name)):
                    continue
                if name not in registered:
                    hit(sf, "KR001", "error", node.lineno,
                        f"env {kind} of unregistered knob {name}",
                        "register it in sparknet_tpu/utils/knobs.py")
                if kind == "read":
                    hit(sf, "KR002", "error", node.lineno,
                        f"env read of {name} bypasses the knob registry",
                        "use sparknet_tpu.utils.knobs.raw/get_* (or a "
                        "helper that delegates there)")
            elif isinstance(node, ast.Call):
                for name in _literal_knob_args(node):
                    if name not in registered:
                        hit(sf, "KR001", "error", node.lineno,
                            f"unregistered knob {name} passed to "
                            f"{dotted(node.func) or 'a call'}()",
                            "register it in sparknet_tpu/utils/knobs.py")

    # shell runners count for usage (CI gate knobs live there)
    for rel in _SHELL_RUNNERS:
        path = project.root / rel
        if path.exists():
            text = path.read_text()
            used.update(n for n in live if n in text)

    ksf = project.by_rel.get(_KNOBS_MODULE)
    if ksf is not None:
        for k in sorted(knobs.all_knobs(), key=lambda k: k.name):
            line = _registration_line(ksf, k.name)
            if not k.removed and k.name not in used:
                hit(ksf, "KR003", "error", line,
                    f"dead registration: {k.name} is registered but never "
                    f"read anywhere in scanned code",
                    "delete the registration or mark it deprecated")
            if not k.doc.strip() or not k.owner.strip():
                hit(ksf, "KR004", "warning", line,
                    f"{k.name} registration is missing "
                    f"{'doc' if not k.doc.strip() else 'owner'}",
                    "every knob carries a one-line doc and an owner module")

    # KNOBS.md drift
    md = project.root / "KNOBS.md"
    want = knobs.knobs_md()
    if not md.exists() or md.read_text() != want:
        if ksf is not None:
            hit(ksf, "KR005", "error", 1,
                "KNOBS.md is missing or stale relative to the registry",
                "run `python tools/lint.py knobs --emit`")
    return findings


def _registration_line(sf: SourceFile, name: str) -> int:
    for i, line in enumerate(sf.text.splitlines(), start=1):
        if f'"{name}"' in line:
            return i
    return 1
